"""Export span telemetry to Chrome/Perfetto ``trace_event`` JSON.

The tracing plane (spark_ensemble_tpu/telemetry/trace.py; docs/tracing.md)
emits every unit of work as a ``"event": "span"`` row in the ordinary
telemetry JSONL stream.  This tool turns one of those streams into a
trace Perfetto (https://ui.perfetto.dev) or ``chrome://tracing`` can open:

- one track per ``(pid, thread)`` — the fit thread, the shard-prefetch
  worker, the checkpoint writer, the fleet router and each replica get
  their own named rows;
- one "X" (complete) slice per span, with the span's attributes as args;
- flow arrows ("s"/"f" pairs) for every causal edge the span stream
  records: hedge and replay dispatches, prefetch-miss waits, and commits
  invalidating speculative round chunks;
- instant markers for ``hedge_fired`` / ``replica_state`` /
  ``request_shed`` events so breaker transitions line up with the slices.

Usage:

    python tools/trace_viewer.py --jsonl telemetry.jsonl --out trace.json
    python tools/trace_viewer.py --jsonl telemetry.jsonl --validate
    # pod mode: several per-host streams (or a directory of them) are
    # stitched into one pod-level trace via telemetry/podview.py —
    # host{i} track groups, clock-offset alignment, cross-host flows
    python tools/trace_viewer.py --jsonl host0.jsonl host1.jsonl --out pod.json
    python tools/trace_viewer.py --jsonl artifacts/ --validate

``--validate`` (also run implicitly before export) checks the span graph:
every non-empty ``parent_id`` must resolve to an emitted span and every
``flow_in`` must have a matching ``flow_out`` source.  Exit code 1 on any
unresolved edge — the CI serving-chaos and streaming jobs gate on it.
A survivor's stream from a preempted pod fails alone (its rewind flow has
no source) and passes stitched — by design: the pod view IS the complete
trace.  stdlib-only: runs anywhere the JSONL landed, no jax required;
podview is loaded by file path so that contract survives pod mode.
"""

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_podview():
    """telemetry/podview.py by file path — a normal package import would
    drag in jax via the package __init__, breaking this tool's
    runs-anywhere contract (podview itself is pure stdlib)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "spark_ensemble_tpu", "telemetry", "podview.py",
    )
    spec = importlib.util.spec_from_file_location("_se_tpu_podview", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

#: standalone event types rendered as instant markers on their track
INSTANT_EVENTS = ("hedge_fired", "replica_state", "request_shed",
                  "slo_alert")


def load_events(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # half-written tail line: the stream is append-only
    return out


def select_spans(
    events: List[Dict[str, Any]], trace_id: Optional[str] = None
) -> List[Dict[str, Any]]:
    spans = [e for e in events if e.get("event") == "span"]
    if trace_id:
        spans = [s for s in spans if s.get("trace_id") == trace_id]
    return spans


def validate(spans: List[Dict[str, Any]]) -> List[str]:
    """Structural problems in a span set (empty list == clean graph):
    unresolved parents (orphan spans) and flow sinks with no source."""
    problems: List[str] = []
    ids = {s.get("span_id") for s in spans}
    sources = set()
    for s in spans:
        for fid in s.get("flow_out") or []:
            sources.add(fid)
    for s in spans:
        pid = s.get("parent_id") or ""
        if pid and pid not in ids:
            problems.append(
                f"orphan span {s.get('span_id')} ({s.get('name')}): "
                f"parent {pid} was never emitted"
            )
        fin = s.get("flow_in")
        if fin is not None and fin not in sources:
            problems.append(
                f"span {s.get('span_id')} ({s.get('name')}): flow_in "
                f"{fin} has no flow_out source"
            )
    return problems


#: span-record keys that are structure, not user attributes ("host" is
#: stamped by podview stitching; single-stream spans never carry it)
_STRUCT_KEYS = (
    "event", "name", "trace_id", "span_id", "parent_id", "ts", "dur_s",
    "pid", "thread", "flow_in", "flow_out", "fit_id", "wall_time", "host",
)


def _tid_for(
    pid: int, thread: Optional[str],
    tids: Dict[Tuple[int, str], int], meta: List[Dict[str, Any]],
) -> int:
    key = (pid, thread or "main")
    if key not in tids:
        # tid 0 reads as the process row in some UIs; start at 1
        tids[key] = len(tids) + 1
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid,
            "tid": tids[key], "args": {"name": key[1]},
        })
    return tids[key]


def to_trace_events(
    spans: List[Dict[str, Any]],
    instants: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Chrome ``trace_event`` JSON (object form) for a span set."""
    tids: Dict[Tuple[int, str], int] = {}
    meta: List[Dict[str, Any]] = []
    out: List[Dict[str, Any]] = []
    named_pids: set = set()
    for s in spans:
        pid = int(s.get("pid", 0))
        # stitched pod traces: name each process row after its host so
        # the viewer shows host{i} track groups (first-seen wins)
        if "host" in s and pid not in named_pids:
            named_pids.add(pid)
            meta.append({
                "ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": f"host{s['host']}"},
            })
        tid = _tid_for(pid, s.get("thread"), tids, meta)
        ts_us = float(s.get("ts", 0.0)) * 1e6
        dur_us = max(float(s.get("dur_s", 0.0)) * 1e6, 1.0)
        args = {k: v for k, v in s.items() if k not in _STRUCT_KEYS}
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        out.append({
            "ph": "X", "name": s.get("name", "?"),
            "cat": s.get("trace_id", "trace"),
            "ts": ts_us, "dur": dur_us, "pid": pid, "tid": tid,
            "args": args,
        })
        # flow arrows: "s" anchored inside the source slice, "f" (bp "e")
        # inside the sink slice — source slices always start no later
        # than their sinks (a hedge's request span predates the twin
        # serve; a committed chunk predates the speculative tail it
        # invalidates), so the arrow renders forward in time
        for fid in s.get("flow_out") or []:
            out.append({
                "ph": "s", "id": int(fid), "name": "flow", "cat": "flow",
                "ts": ts_us, "pid": pid, "tid": tid,
            })
        fin = s.get("flow_in")
        if fin is not None:
            out.append({
                "ph": "f", "bp": "e", "id": int(fin), "name": "flow",
                "cat": "flow", "ts": ts_us + 1.0, "pid": pid, "tid": tid,
            })
    for e in instants or []:
        pid = int(e.get("pid", 0))
        tid = _tid_for(pid, e.get("thread"), tids, meta)
        args = {
            k: v for k, v in e.items()
            if k not in ("event", "ts", "pid", "thread", "wall_time")
        }
        out.append({
            "ph": "i", "s": "t", "name": e.get("event", "?"),
            "cat": "marker",
            "ts": float(e.get("ts", e.get("wall_time", 0.0))) * 1e6,
            "pid": pid, "tid": tid, "args": args,
        })
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def export_events(
    events: List[Dict[str, Any]],
    out_path: Optional[str] = None,
    trace_id: Optional[str] = None,
    hosts: Optional[List[int]] = None,
) -> Dict[str, Any]:
    """Validate + convert an in-memory event list (one stream's, or the
    pod-stitched merge); returns a summary dict (the CLI prints it).
    Raises ``ValueError`` on an unresolved span graph."""
    spans = select_spans(events, trace_id=trace_id)
    problems = validate(spans)
    if problems:
        raise ValueError(
            f"{len(problems)} unresolved span edges:\n  "
            + "\n  ".join(problems)
        )
    # standalone events already carry a wall-clock "ts" (emit_event)
    instants = [e for e in events if e.get("event") in INSTANT_EVENTS]
    trace = to_trace_events(spans, instants)
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(trace, fh)
    tracks = {
        (s.get("pid"), s.get("thread") or "main") for s in spans
    }
    flows = sum(len(s.get("flow_out") or []) for s in spans)
    summary = {
        "spans": len(spans),
        "tracks": len(tracks),
        "flows": flows,
        "instants": len(instants),
        "traces": sorted({s.get("trace_id", "") for s in spans}),
        "out": out_path,
    }
    if hosts is not None:
        summary["hosts"] = hosts
    return summary


def export(
    jsonl_path: str,
    out_path: Optional[str] = None,
    trace_id: Optional[str] = None,
) -> Dict[str, Any]:
    """Single-stream entry point: load one JSONL file, then
    :func:`export_events`."""
    return export_events(load_events(jsonl_path), out_path, trace_id=trace_id)


def _resolve_events(
    inputs: List[str],
) -> Tuple[List[Dict[str, Any]], Optional[List[int]]]:
    """One file → that stream untouched; several files or any directory →
    the pod-stitched merge.  Returns (events, hosts-or-None)."""
    if len(inputs) == 1 and not os.path.isdir(inputs[0]):
        return load_events(inputs[0]), None
    pv = _load_podview()
    merged, info = pv.stitch_files(inputs)
    return merged, info["hosts"]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jsonl", required=True, nargs="+",
                        help="telemetry JSONL stream(s) to read; several "
                             "files or a directory are stitched into one "
                             "pod-level trace")
    parser.add_argument("--out", default=None,
                        help="write Perfetto trace_event JSON here")
    parser.add_argument("--trace", default=None,
                        help="only export this trace_id")
    parser.add_argument("--validate", action="store_true",
                        help="only check the span graph; no export")
    args = parser.parse_args(argv)
    events, hosts = _resolve_events(args.jsonl)
    if args.validate and not args.out:
        spans = select_spans(events, trace_id=args.trace)
        problems = validate(spans)
        for p in problems:
            print(f"UNRESOLVED: {p}", file=sys.stderr)
        summary = {"spans": len(spans), "problems": len(problems)}
        if hosts is not None:
            summary["hosts"] = hosts
        print(json.dumps(summary))
        return 1 if problems else 0
    try:
        summary = export_events(events, args.out, trace_id=args.trace,
                                hosts=hosts)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
