#!/usr/bin/env python
"""Repo entry point for graftlint (docs/static_analysis.md).

Thin wrapper so `python tools/graftlint.py` works from a checkout
without installation; the installed console script (`graftlint`, see
pyproject.toml) routes to the same `spark_ensemble_tpu.analysis.cli`.

    python tools/graftlint.py                  # tier-1 lint, repo targets
    python tools/graftlint.py --contracts      # + tier-2 traced contracts
    python tools/graftlint.py --update-baseline
    python tools/graftlint.py --list-rules
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_ensemble_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
