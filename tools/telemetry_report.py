#!/usr/bin/env python
"""Render a telemetry JSONL stream (SE_TPU_TELEMETRY / telemetry_path) into
the per-phase cost table ``spark_ensemble_tpu.utils.profiling`` produces
from profiler traces — same columns, same shapes, so the two views of a run
read (and diff) the same way:

    SE_TPU_TELEMETRY=/tmp/fit.jsonl python train.py
    python tools/telemetry_report.py /tmp/fit.jsonl

Per fit: the ``fit_end`` phase map as a total_ms/%/count table (count = the
rounds that contributed to the phase), round statistics, compile counts,
and — when a ``phase_probe`` event is present — the probe's fine-phase
split.  ``--jsonl PATH`` re-emits the aggregated table as
``{"op","total_us","count","share"}`` records (the format
``utils/profiling.py --jsonl`` writes), and ``--diff OTHER.jsonl`` compares
against such a file.

Pure stdlib + the profiling formatter: usable on a host with no jax.

Pod mode: pass several per-host JSONL files (or a directory of them) and
the report appends a ``== pod skew ==`` section — per-host sweep/fetch/
reduce/shard-wait totals, per-round max/median skew ratios with the
offending host, and injected-stall attribution (telemetry/podview.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_ensemble_tpu.telemetry import podview  # noqa: E402
from spark_ensemble_tpu.utils.profiling import (  # noqa: E402
    format_summary,
    rows_to_records,
    write_jsonl,
)


def load_events(path: str) -> List[dict]:
    events = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                print(
                    f"warning: {path}:{line_no}: bad JSON ({e})",
                    file=sys.stderr,
                )
    return events


def group_fits(events: List[dict]) -> Dict[str, List[dict]]:
    fits: Dict[str, List[dict]] = {}
    for ev in events:
        fits.setdefault(ev.get("fit_id", "?"), []).append(ev)
    return fits


def fit_phase_rows(
    fit_events: List[dict],
) -> Tuple[List[Tuple[str, float, int]], float]:
    """``fit_end`` phases -> profiling-shaped rows [(name, total_us, count)]
    + grand total; the per-phase count is the number of round_end events
    charged to it (1 for one-shot phases like setup/finalize)."""
    fit_end = next(
        (e for e in fit_events if e.get("event") == "fit_end"), None
    )
    if fit_end is None:
        return [], 0.0
    round_counts: Dict[str, int] = {}
    for ev in fit_events:
        if ev.get("event") != "round_end":
            continue
        for name in ev.get("phases", {"rounds": None}):
            # chunked round phases land in the fit-level "rounds" bucket;
            # member fits in "rounds" too (see FitTelemetry)
            round_counts["rounds"] = round_counts.get("rounds", 0) + 1
            break
    rows = []
    for name, secs in fit_end.get("phases", {}).items():
        rows.append((name, float(secs) * 1e6, round_counts.get(name, 1)))
    rows.sort(key=lambda r: -r[1])
    total = sum(r[1] for r in rows)
    return rows, total


def round_stats(fit_events: List[dict]) -> Optional[dict]:
    ends = [e for e in fit_events if e.get("event") == "round_end"]
    if not ends:
        return None
    durs = sorted(float(e.get("duration_s", 0.0)) for e in ends)
    losses = [e["loss"] for e in ends if "loss" in e]
    out = {
        "rounds": len(ends),
        "mean_s": sum(durs) / len(durs),
        "p50_s": durs[len(durs) // 2],
        "max_s": durs[-1],
    }
    if losses:
        out["first_loss"] = losses[0]
        out["last_loss"] = losses[-1]
    return out


def round_cost_line(fit_events: List[dict]) -> Optional[str]:
    """Static round-cost summary from the round_end events: the resolved
    histogram tier, packed-lane width, modeled HBM bytes per round, and the
    MFU estimate against the static flop count (ops/tree.py
    ``round_cost_est``).  One line per fit — the fields are shape-derived
    and identical across rounds."""
    ev = next(
        (
            e
            for e in fit_events
            if e.get("event") == "round_end" and "hist_tier" in e
        ),
        None,
    )
    if ev is None:
        return None
    parts = [f"hist_tier: {ev['hist_tier']}"]
    bits = ev.get("pack_bits")
    if bits:
        parts.append(f"pack {bits}-bit")
    hbm = ev.get("hbm_bytes_est")
    if hbm is not None:
        parts.append(f"hbm/round {float(hbm) / 2**20:.2f} MiB")
    mfu = ev.get("mfu_est")
    if mfu is not None:
        parts.append(f"mfu_est {100.0 * float(mfu):.2f}%")
    return "  ".join(parts)


def sampling_line(fit_events: List[dict]) -> Optional[str]:
    """Gradient-based row sampling summary: the method and rates from the
    fit's ``sampling_config`` event plus the compacted bucket and the
    modeled per-round HBM saving the round_end cost fields carry
    (models/gbm.py GOSS/MVS).  Fits with ``sampling='none'`` emit no
    config event and get no line."""
    cfg = next(
        (e for e in fit_events if e.get("event") == "sampling_config"), None
    )
    if cfg is None:
        return None
    parts = [f"sampling: {cfg.get('method')}"]
    if cfg.get("method") == "mvs":
        parts.append(f"lambda {float(cfg.get('mvs_lambda', 0.0)):g}")
    else:
        parts.append(
            f"rates {float(cfg.get('top_rate', 0.0)):g}"
            f"/{float(cfg.get('other_rate', 0.0)):g}"
        )
    rows = cfg.get("sampled_rows")
    bucket = cfg.get("sample_bucket")
    if rows is not None and bucket is not None:
        parts.append(f"rows {int(rows)} -> bucket {int(bucket)}")
    ev = next(
        (
            e
            for e in fit_events
            if e.get("event") == "round_end" and "hbm_saved_est" in e
        ),
        None,
    )
    if ev is not None:
        parts.append(
            f"hbm saved/round {float(ev['hbm_saved_est']) / 2**20:.2f} MiB"
        )
    return "  ".join(parts)


def cost_model_line(fit_events: List[dict]) -> Optional[str]:
    """Measured-vs-estimated ledger: median modeled round time (roofline
    from ``round_cost_est``) against the median measured round, the
    resulting error, and the recompiles the ledger attributed to round
    chunks.  Only fits whose round_end events carry ``modeled_s`` (i.e.
    emitted after the ledger landed) get the line."""
    ends = [
        e
        for e in fit_events
        if e.get("event") == "round_end" and "modeled_s" in e
    ]
    if not ends:
        return None
    modeled = sorted(float(e["modeled_s"]) for e in ends)
    measured = sorted(float(e.get("duration_s", 0.0)) for e in ends)
    parts = [
        f"cost model: modeled {modeled[len(modeled) // 2] * 1e3:.2f}ms/round"
        f"  measured {measured[len(measured) // 2] * 1e3:.2f}ms/round"
    ]
    errs = sorted(
        float(e["cost_model_error_pct"])
        for e in ends
        if "cost_model_error_pct" in e
    )
    if errs:
        parts.append(f"error {errs[len(errs) // 2]:.1f}%")
    compiles = sum(int(e.get("chunk_compiles", 0)) for e in ends)
    if compiles:
        parts.append(f"chunk compiles {compiles}")
    return "  ".join(parts)


def xla_cost_line(fit_events: List[dict]) -> Optional[str]:
    """The three-way cost line (docs/operator.md): measured wall vs the
    analytic roofline (``modeled_s``) vs XLA's own cost model
    (``xla_modeled_s``), with MFU recomputed from XLA flops and the
    XLA/analytic flop ratio.  Only fits whose round_end events carry the
    programz join fields (telemetry/programz.py live + analyzed) get it."""
    ends = [
        e
        for e in fit_events
        if e.get("event") == "round_end" and "xla_flops" in e
    ]
    if not ends:
        return None

    def med(key: str) -> Optional[float]:
        vals = sorted(float(e[key]) for e in ends if key in e)
        return vals[len(vals) // 2] if vals else None

    measured = med("duration_s")
    analytic = med("modeled_s")
    xla = med("xla_modeled_s")
    parts = []
    if measured is not None:
        parts.append(f"measured {measured * 1e3:.2f}ms/round")
    if analytic is not None:
        parts.append(f"analytic {analytic * 1e3:.2f}ms/round")
    if xla is not None:
        parts.append(f"xla {xla * 1e3:.2f}ms/round")
    mfu = med("mfu_xla")
    if mfu is not None:
        parts.append(f"mfu_xla {100.0 * mfu:.2f}%")
    ratio = med("xla_vs_analytic_flops_ratio")
    if ratio is not None:
        parts.append(f"xla/analytic flops {ratio:.2f}")
    return "xla cost: " + "  ".join(parts)


def program_table(events: List[dict], top: int = 10) -> Optional[str]:
    """Per-program top-N table from ``program`` events — the
    ``/programz`` rows an operator plane emitted into the stream
    (``ProgramInventory.emit_rows`` / ``serving_smoke.py fleet``).
    Heaviest program first (XLA flops, then calls), one row each."""
    rows = [e for e in events if e.get("event") == "program"]
    if not rows:
        return None
    # the inventory re-emits on every snapshot: keep the last row per
    # (tag, signature) so long-running streams do not duplicate programs
    latest: Dict[Tuple[str, str], dict] = {}
    for e in rows:
        latest[(e.get("tag", "?"), json.dumps(e.get("signature")))] = e
    ordered = sorted(
        latest.values(),
        key=lambda e: (
            -float(e.get("flops", 0.0)),
            -int(e.get("calls", 0)),
            e.get("tag", "?"),
        ),
    )[: max(int(top), 0)]
    lines = [
        f"{'gflops':>8}  {'MiB':>8}  {'calls':>6}  {'build_ms':>9}  "
        f"{'status':<11} tag"
    ]
    for e in ordered:
        flops = float(e.get("flops", 0.0))
        nbytes = float(e.get("bytes_accessed", 0.0))
        build = e.get("build_s")
        lines.append(
            f"{flops / 1e9:>8.3f}  {nbytes / 2**20:>8.2f}  "
            f"{int(e.get('calls', 0)):>6}  "
            + (f"{float(build) * 1e3:>9.2f}  " if build is not None
               else f"{'-':>9}  ")
            + f"{e.get('status', '?'):<11} {e.get('tag', '?')}"
        )
    return "\n".join(lines)


def shard_io_line(fit_events: List[dict]) -> Optional[str]:
    """Shard-I/O summary for streaming fits (data/streaming.py): bytes
    pulled through the prefetcher, prefetch hit rate, and — the number the
    prefetcher exists to minimize — the shard_wait share of wall (host
    time spent waiting on a shard the worker had not finished loading)."""
    loads = [e for e in fit_events if e.get("event") == "shard_load"]
    if not loads:
        return None
    hits = [e for e in fit_events if e.get("event") == "shard_prefetch_hit"]
    waits = [e for e in fit_events if e.get("event") == "shard_wait_us"]
    n_loads = sum(int(e.get("count", 0)) for e in loads)
    total_bytes = sum(int(e.get("bytes", 0)) for e in loads)
    load_s = sum(float(e.get("duration_us", 0.0)) for e in loads) / 1e6
    wait_s = sum(float(e.get("wait_us", 0.0)) for e in waits) / 1e6
    n_hits = sum(int(e.get("hits", 0)) for e in hits)
    n_total = n_hits + sum(int(e.get("misses", 0)) for e in hits)
    parts = [
        f"shard I/O: {n_loads} loads  {total_bytes / 2**20:.2f} MiB  "
        f"load {load_s * 1e3:.1f}ms  wait {wait_s * 1e3:.1f}ms"
    ]
    if n_total:
        parts.append(f"prefetch hits {100.0 * n_hits / n_total:.1f}%")
    fit_end = next(
        (e for e in fit_events if e.get("event") == "fit_end"), None
    )
    wall_s = float(fit_end.get("wall_s", 0.0)) if fit_end else 0.0
    if wall_s > 0:
        parts.append(f"wait share {100.0 * wait_s / wall_s:.1f}% of wall")
    return "  ".join(parts)


def fleet_slo_line(fit_events: List[dict]) -> Optional[str]:
    """Fleet SLO summary (serving/fleet.py): the aggregate ``fleet_slo``
    row's request latency percentiles plus the resilience counters —
    hedges fired/won, replays, crashes absorbed, degraded share."""
    agg = next(
        (
            e
            for e in reversed(fit_events)
            if e.get("event") == "fleet_slo" and e.get("replica") == "*"
        ),
        None,
    )
    if agg is None:
        return None
    parts = [
        f"fleet SLO: {int(agg.get('requests', 0))} requests  "
        f"p50 {float(agg.get('p50_ms', 0.0)):.2f}ms  "
        f"p99 {float(agg.get('p99_ms', 0.0)):.2f}ms"
    ]
    hedges = int(agg.get("hedges_fired", 0))
    if hedges:
        parts.append(f"hedges {hedges} ({int(agg.get('hedges_won', 0))} won)")
    for k in ("replays", "crashes", "shed"):
        if int(agg.get(k, 0)):
            parts.append(f"{k} {int(agg[k])}")
    share = float(agg.get("degraded_share", 0.0))
    if share:
        parts.append(f"degraded {100.0 * share:.1f}%")
    return "  ".join(parts)


def sweep_ledger_line(fit_events: List[dict]) -> Optional[str]:
    """Per-candidate round ledger for megabatch sweep fits
    (models/gbm_sweep.py): chunked dispatch count, config-lane width,
    live lane-rounds executed vs the slab's padded capacity (lanes past
    their own round budget or patience stop ride at scale 0 — the
    successive-halving occupancy), and the amortized per-candidate round
    cost."""
    chunks = [e for e in fit_events if e.get("event") == "sweep_chunk"]
    if not chunks:
        return None
    active = sum(int(e.get("active_lane_rounds", 0)) for e in chunks)
    capacity = sum(
        int(e.get("rounds", 0)) * int(e.get("candidates", 0))
        for e in chunks
    )
    wall = sum(float(e.get("wall_s", 0.0)) for e in chunks)
    lanes = max(int(e.get("candidates", 0)) for e in chunks)
    parts = [
        f"sweep: {len(chunks)} chunk dispatches  {lanes} lanes  "
        f"{active} live lane-rounds"
    ]
    if capacity:
        parts.append(f"occupancy {100.0 * active / capacity:.1f}%")
    if active:
        parts.append(f"{wall / active * 1e3:.2f}ms/candidate-round")
    return "  ".join(parts)


def tuning_section(events: List[dict]) -> Optional[str]:
    """Hyperparameter-sweep summary (docs/selection.md#megabatch-sweeps)
    from the ``tuning_candidate`` events CrossValidator /
    TrainValidationSplit emit per (param-map, fold) candidate: the
    candidate count and megabatch share per tuner, then a per-map table
    of mean metric, fitted rounds and attributed wall.  Metric direction
    lives in the evaluator, so rows render in map order — the tuner's
    own best_index is the verdict, this table is the evidence."""
    cands = [e for e in events if e.get("event") == "tuning_candidate"]
    if not cands:
        return None
    lines = []
    by_tuner: Dict[str, List[dict]] = {}
    for e in cands:
        by_tuner.setdefault(e.get("tuner", "?"), []).append(e)
    for tuner in sorted(by_tuner):
        evs = by_tuner[tuner]
        maps = len({int(e.get("map_index", 0)) for e in evs})
        folds = len({int(e.get("fold", 0)) for e in evs})
        mb = sum(1 for e in evs if e.get("megabatch"))
        wall = sum(float(e.get("wall_s", 0.0)) for e in evs)
        lines.append(
            f"{tuner}: {len(evs)} candidates ({maps} maps x {folds} "
            f"folds)  megabatch {mb}/{len(evs)}  wall {wall:.3f}s"
        )
        by_map: Dict[int, List[dict]] = {}
        for e in evs:
            by_map.setdefault(int(e.get("map_index", 0)), []).append(e)
        lines.append(
            f"{'map':>4}  {'mean_metric':>12}  {'rounds':>7}  {'wall_s':>8}"
        )
        for mi in sorted(by_map):
            mevs = by_map[mi]
            mean = sum(float(e.get("metric", 0.0)) for e in mevs) / len(mevs)
            rounds = max(int(e.get("rounds", 0)) for e in mevs)
            mwall = sum(float(e.get("wall_s", 0.0)) for e in mevs)
            lines.append(
                f"{mi:>4}  {mean:>12.6g}  {rounds:>7}  {mwall:>8.3f}"
            )
    return "\n".join(lines)


def quality_section(events: List[dict]) -> Optional[str]:
    """Model-quality plane summary (docs/quality.md) from the
    ``drift_window`` / ``shadow_eval`` / ``quality_alert`` events plus
    the attribution fields riding ``fleet_request``: per-stream drift
    windows with the top drifting features by PSI, shadow divergence,
    sampled-uncertainty quantiles, and every alert transition."""
    windows = [e for e in events if e.get("event") == "drift_window"]
    shadows = [e for e in events if e.get("event") == "shadow_eval"]
    alerts = [e for e in events if e.get("event") == "quality_alert"]
    unc = sorted(
        float(e["uncertainty"])
        for e in events
        if e.get("event") == "fleet_request" and "uncertainty" in e
    )
    if not (windows or shadows or alerts or unc):
        return None
    lines = []
    by_drift: Dict[str, List[dict]] = {}
    for e in windows:
        by_drift.setdefault(e.get("fit_id", "?"), []).append(e)
    for stream in sorted(by_drift):
        evs = by_drift[stream]
        last = evs[-1]
        rows = sum(int(e.get("rows", 0)) for e in evs)
        worst = max(float(e.get("psi_max", 0.0)) for e in evs)
        lines.append(
            f"drift[{stream}]: {len(evs)} windows  {rows} rows  "
            f"psi_max {float(last.get('psi_max', 0.0)):.3f} "
            f"(worst {worst:.3f})  "
            f"kl_max {float(last.get('kl_max', 0.0)):.3f}  "
            f"drifted {int(last.get('drifted_features', 0))}"
        )
        top = last.get("top") or {}
        if top:
            ranked = "  ".join(
                f"{k} {float(v):.3f}"
                for k, v in sorted(top.items(), key=lambda kv: -kv[1])
            )
            lines.append(f"  top psi: {ranked}")
    by_shadow: Dict[str, List[dict]] = {}
    for e in shadows:
        by_shadow.setdefault(e.get("fit_id", "?"), []).append(e)
    for stream in sorted(by_shadow):
        evs = by_shadow[stream]
        last = evs[-1]
        srows = sum(int(e.get("rows", 0)) for e in evs)
        lines.append(
            f"shadow[{stream}]: candidate {last.get('candidate', '?')}  "
            f"{len(evs)} evals  {srows} rows  "
            f"divergence {float(last.get('rolling_divergence', 0.0)):.3f}"
        )
    if unc:
        def q(p: float) -> float:
            return unc[min(len(unc) - 1, int(p * len(unc)))]

        flagged = sum(
            1
            for e in events
            if e.get("event") == "fleet_request"
            and e.get("quality_flagged")
        )
        lines.append(
            f"uncertainty: {len(unc)} sampled  p50 {q(0.5):.3f}  "
            f"p90 {q(0.9):.3f}  max {unc[-1]:.3f}  flagged {flagged}"
        )
    for a in alerts:
        lines.append(
            f"alert {a.get('state', '?')}: {a.get('metric', '?')} "
            f"{float(a.get('value', 0.0)):.3f} vs "
            f"{float(a.get('threshold', 0.0)):.3f} [{a.get('fit_id', '?')}]"
        )
    return "\n".join(lines)


def render_fit(fit_id: str, fit_events: List[dict]) -> str:
    lines = [f"== {fit_id} =="]
    start = next(
        (e for e in fit_events if e.get("event") == "fit_start"), None
    )
    fit_end = next(
        (e for e in fit_events if e.get("event") == "fit_end"), None
    )
    if start:
        dims = ", ".join(
            f"{k}={start[k]}" for k in ("n", "d", "num_classes") if k in start
        )
        if dims:
            lines.append(f"dataset: {dims}")
    rows, total = fit_phase_rows(fit_events)
    if rows:
        lines.append(format_summary(rows, total))
    if fit_end:
        lines.append(
            f"wall: {float(fit_end.get('wall_s', 0.0)):.3f}s  "
            f"compiles: {fit_end.get('compile_count', '?')} "
            f"({float(fit_end.get('compile_s', 0.0)):.3f}s)"
        )
        blocked_us = fit_end.get("host_blocked_us")
        if blocked_us is not None:
            wall_s = float(fit_end.get("wall_s", 0.0))
            share = (
                f" ({blocked_us / 1e4 / wall_s:.1f}% of wall)"
                if wall_s > 0
                else ""
            )
            lines.append(
                f"host_blocked: {float(blocked_us) / 1e3:.1f}ms{share}"
            )
        mem = fit_end.get("memory") or {}
        for dev, stats in sorted(mem.items()):
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                lines.append(f"memory[{dev}]: peak {peak / 2**20:.1f} MiB")
    stats = round_stats(fit_events)
    if stats:
        loss_part = (
            f"  loss {stats['first_loss']:.6g} -> {stats['last_loss']:.6g}"
            if "first_loss" in stats
            else ""
        )
        lines.append(
            f"rounds: {stats['rounds']}  mean {stats['mean_s'] * 1e3:.2f}ms  "
            f"p50 {stats['p50_s'] * 1e3:.2f}ms  max {stats['max_s'] * 1e3:.2f}ms"
            f"{loss_part}"
        )
    cost = round_cost_line(fit_events)
    if cost:
        lines.append(cost)
    samp = sampling_line(fit_events)
    if samp:
        lines.append(samp)
    model = cost_model_line(fit_events)
    if model:
        lines.append(model)
    xla = xla_cost_line(fit_events)
    if xla:
        lines.append(xla)
    shard_io = shard_io_line(fit_events)
    if shard_io:
        lines.append(shard_io)
    fleet = fleet_slo_line(fit_events)
    if fleet:
        lines.append(fleet)
    sweep = sweep_ledger_line(fit_events)
    if sweep:
        lines.append(sweep)
    probe = next(
        (e for e in fit_events if e.get("event") == "phase_probe"), None
    )
    if probe:
        probe_rows = sorted(
            ((k, float(v) * 1e6, 1) for k, v in probe["phases"].items()),
            key=lambda r: -r[1],
        )
        lines.append("fine-phase probe (single round, representative):")
        lines.append(format_summary(probe_rows, sum(r[1] for r in probe_rows)))
    return "\n".join(lines)


def aggregate_rows(
    fits: Dict[str, List[dict]],
) -> Tuple[List[Tuple[str, float, int]], float]:
    """Phase rows summed over every fit in the stream (for --jsonl/--diff)."""
    merged: Dict[str, List[float]] = {}
    for fit_events in fits.values():
        for name, us, count in fit_phase_rows(fit_events)[0]:
            slot = merged.setdefault(name, [0.0, 0])
            slot[0] += us
            slot[1] += count
    rows = sorted(
        ((n, v[0], int(v[1])) for n, v in merged.items()), key=lambda r: -r[1]
    )
    return rows, sum(r[1] for r in rows)


def render_diff(records_a: List[dict], records_b: List[dict]) -> str:
    a = {r["op"]: r for r in records_a}
    b = {r["op"]: r for r in records_b}
    lines = [f"{'total_ms':>10}  {'other_ms':>10}  {'delta%':>7}  op"]
    for op in sorted(set(a) | set(b), key=lambda o: -(a.get(o, b.get(o))["total_us"])):
        ua = a.get(op, {}).get("total_us", 0.0)
        ub = b.get(op, {}).get("total_us", 0.0)
        delta = math.inf if ub == 0 else 100.0 * (ua - ub) / ub
        lines.append(
            f"{ua / 1e3:>10.3f}  {ub / 1e3:>10.3f}  {delta:>7.1f}  {op}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "jsonl_path",
        nargs="+",
        help="telemetry JSONL stream(s) to render; several files or a "
        "directory of per-host streams add the pod skew section",
    )
    ap.add_argument(
        "--fit",
        help="only render fits whose fit_id contains this substring",
    )
    ap.add_argument(
        "--jsonl",
        metavar="PATH",
        help="write the phase table aggregated over all fits as "
        '{"op","total_us","count","share"} records',
    )
    ap.add_argument(
        "--diff",
        metavar="PATH",
        help="compare against another {op,total_us,...} JSONL (from this "
        "tool or utils/profiling.py --jsonl)",
    )
    args = ap.parse_args(argv)
    streams: Optional[List[List[dict]]] = None
    if len(args.jsonl_path) == 1 and not os.path.isdir(args.jsonl_path[0]):
        events = load_events(args.jsonl_path[0])
    else:
        inputs = podview.expand_inputs(args.jsonl_path)
        streams = [load_events(p) for p in inputs]
        events = [ev for stream in streams for ev in stream]
    if not events:
        print(f"no telemetry events found in {', '.join(args.jsonl_path)}")
        return 1
    fits = group_fits(events)
    if args.fit:
        fits = {k: v for k, v in fits.items() if args.fit in k}
        if not fits:
            print(f"no fit_id matching {args.fit!r}")
            return 1
    # events summarized in their own section below — a stream holding
    # nothing else is not a fit and must not render as an empty one
    sectioned = {
        "drift_window", "shadow_eval", "quality_alert", "tuning_candidate",
    }
    for fit_id in sorted(fits):
        if all(e.get("event") in sectioned for e in fits[fit_id]):
            continue  # summarized in == model quality == / == tuning ==
        print(render_fit(fit_id, fits[fit_id]))
        print()
    programs = program_table(events)
    if programs:
        print("== programz ==")
        print(programs)
        print()
    quality = quality_section(
        [ev for evs in fits.values() for ev in evs]
    )  # respects --fit: quality streams filter like any other fit_id
    if quality:
        print("== model quality ==")
        print(quality)
        print()
    tuning = tuning_section([ev for evs in fits.values() for ev in evs])
    if tuning:
        print("== tuning ==")
        print(tuning)
        print()
    if streams is not None:
        skew = podview.skew_report(streams)
        # a lone host has no pod to skew against — only render when the
        # inputs span hosts (or a chaos stall demands attribution), so a
        # directory holding one stream matches the single-file output
        if len(skew["hosts"]) > 1 or skew["stalls"]:
            print(podview.render_skew(skew))
            print()
    rows, total = aggregate_rows(fits)
    if args.jsonl:
        write_jsonl(rows_to_records(rows, total), args.jsonl)
    if args.diff:
        other = load_events(args.diff)
        print("diff vs", args.diff)
        print(render_diff(rows_to_records(rows, total), other))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
