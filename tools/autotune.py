"""Offline autotune search CLI (docs/autotune.md).

Measures every registered tunable's candidate grid with real jitted
dispatches on THIS process's default backend and publishes the winners to
the on-disk tuning cache, where `resolve()` picks them up transparently in
later processes (mode `cache`, the default):

    python tools/autotune.py --budget smoke          # CI: ~1 min
    python tools/autotune.py --budget full           # letter-shaped, ~10 min
    python tools/autotune.py --budget fast --groups fit,predict --json

The cache location follows ``SE_TPU_AUTOTUNE_CACHE`` (or
``~/.cache/spark_ensemble_tpu/autotune``); ``--out`` overrides it for this
run.  ``--no-save`` measures and reports without publishing (dry run).
Winners only displace a default when they beat it by more than the noise
floor, so a republished cache can only keep or improve steady-state
throughput.  Exit code 0 = search completed and (unless --no-save) the
cache published atomically.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    from spark_ensemble_tpu.autotune.search import BUDGETS, _GROUPS

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--budget", choices=sorted(BUDGETS), default="fast",
        help="search workload size: smoke (CI), fast, full (letter-shaped)",
    )
    parser.add_argument(
        "--groups", default=None,
        help=f"comma-separated subset of {','.join(_GROUPS)} (default: all)",
    )
    parser.add_argument(
        "--out", default=None,
        help="cache directory to publish to (default: SE_TPU_AUTOTUNE_CACHE "
        "or ~/.cache/spark_ensemble_tpu/autotune)",
    )
    parser.add_argument(
        "--no-save", action="store_true",
        help="measure and report only; do not publish the cache",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the full result (winners + per-candidate timings) as JSON",
    )
    args = parser.parse_args(argv)

    from spark_ensemble_tpu.autotune import ensure_compilation_cache, run_search

    ensure_compilation_cache()
    groups = (
        tuple(g.strip() for g in args.groups.split(",") if g.strip())
        if args.groups else None
    )
    res = run_search(
        budget=args.budget,
        groups=groups,
        save=not args.no_save,
        directory=args.out,
    )
    if args.json:
        print(json.dumps(res, indent=2, sort_keys=True))
        return 0
    print(f"platform={res['platform']} device_kind={res['device_kind']} "
          f"shape_class={res['shape_class']} budget={res['budget']}")
    for name, per_candidate in res["timings"].items():
        best = min(per_candidate, key=per_candidate.get)
        row = " | ".join(
            f"{c}{'*' if c == best else ''} {t * 1e3:.1f}ms"
            for c, t in per_candidate.items()
        )
        print(f"  {name}: {row}")
    if res["winners"]:
        print("winners (beat the default by > noise floor):")
        for name, val in sorted(res["winners"].items()):
            print(f"  {name} = {val}")
    else:
        print("winners: none (defaults already optimal on this backend)")
    if res.get("cache_path"):
        print(f"published: {res['cache_path']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
